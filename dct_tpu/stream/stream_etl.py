"""Exactly-once streaming delta ETL under the frozen z-score basis.

One pass = one transaction: poll a batch off the consumer group,
transform it with the PR 10 machinery (frozen basis, Chan-merged
cumulative moments, rebuild tolerance — reused unchanged from
:mod:`dct_tpu.etl.preprocess`), publish ONE parquet part named by the
batch's offset range, then commit the consumed offsets with the whole
new ``etl_state`` payload riding in the commit's ``meta``. The commit
is the only durability point that counts:

- crash BETWEEN transform and commit: the part file exists but its
  start offset is at/after the committed total — the next pass deletes
  it as an orphan and replays the same records from the committed
  vector (partition order is fixed, so the replay is the same rows);
  zero duplicates by construction;
- crash AFTER commit but before ``etl_state.json``: the next pass
  heals the state file FROM the commit meta, so the trainer only ever
  observes generation N once generation N's rows are both published
  and committed.

Part naming: ``part-stream-<start>-<end>.parquet`` over the FLATTENED
offset total (sum across partitions) — monotone, so orphan detection
is a name comparison. The trainer's loader globs ``*.parquet`` exactly
as it does for the polling path's ``part-NNNNN`` files.

When the merged full-distribution stats drift past
``DCT_ETL_REBUILD_TOL`` (the same :func:`~dct_tpu.etl.preprocess
._basis_stale` gate the CSV path uses), the pass re-reads the WHOLE
log from offset zero and republishes the snapshot under a fresh basis
with the same atomic directory swap the CSV rebuild uses.
"""

from __future__ import annotations

import os
import re
import shutil
import time

from dct_tpu.etl.preprocess import (
    DEFAULT_FEATURES,
    ETL_STATE_VERSION,
    _accum_from,
    _basis_stale,
    _chan_merge,
    _moments_stats,
    _publish_part,
    _rebuild_tolerance,
    _stats_from_accum,
    _transform_columns,
    _write_etl_state,
    persist_stats_and_drift,
    read_etl_state,
    read_previous_stats,
)
from dct_tpu.stream.consumer import ConsumerGroup, read_commit
from dct_tpu.stream.log import TS_KEY

_PART_RE = re.compile(r"^part-stream-(\d{12})-(\d{12})\.parquet$")


def _part_name(start: int, end: int) -> str:
    return f"part-stream-{start:012d}-{end:012d}.parquet"


def _records_table(records: list[dict], feature_cols: list[str],
                   label_col: str):
    """Arrow table from event records — feature columns coerced through
    ``float()`` (correctly-rounded, same IEEE double the CSV parser
    yields for the same decimal text) so a stream-fed snapshot is
    bit-identical to a file-fed one over the same logical rows."""
    import pyarrow as pa

    cols: dict = {}
    for name in feature_cols:
        cols[name] = pa.array(
            [float(r[name]) for r in records], type=pa.float64()
        )
    cols[label_col] = pa.array([str(r[label_col]) for r in records])
    return pa.table(cols)


def _remove_orphan_parts(
    parquet_dir: str, committed_total: int, *, emit=None
) -> int:
    """Delete stream parts whose start offset is at/after the committed
    total: output of a torn attempt that never reached its commit. The
    replay re-publishes the same rows under a fresh range name."""
    removed = 0
    try:
        names = os.listdir(parquet_dir)
    except OSError:
        return 0
    for name in names:
        m = _PART_RE.match(name)
        if m and int(m.group(1)) >= committed_total:
            try:
                os.remove(os.path.join(parquet_dir, name))
            except OSError:
                continue
            removed += 1
            if emit is not None:
                emit(
                    "stream", "stream.replay",
                    orphan_part=name, committed_total=committed_total,
                )
    return removed


def _heal_state_from_commit(output_dir: str, commit: dict) -> dict:
    """Re-derive ``etl_state.json`` from the last commit's meta when a
    crash separated the two (commit wins — it is the transaction)."""
    meta = commit.get("meta") or {}
    state = read_etl_state(output_dir)
    if (
        meta.get("version") == ETL_STATE_VERSION
        and int(meta.get("generation") or 0)
        > int(state.get("generation") or 0)
    ):
        _write_etl_state(output_dir, meta)
        return meta
    return state


def _read_all_records(consumer: ConsumerGroup,
                      upto: list[int]) -> list[tuple[int, int, dict]]:
    """Every record from offset zero up to the ``upto`` vector (the
    full-rebuild read)."""
    out: list[tuple[int, int, dict]] = []
    log = consumer.log
    for k in range(log.n_partitions):
        off = 0
        while off < upto[k]:
            got = log.read(k, off, max_records=upto[k] - off)
            if not got:
                break
            out.extend((k, o, r) for o, r in got)
            off = got[-1][0] + 1
    return out


def _record_stream_lineage(
    parquet_dir: str,
    basis: dict,
    prev_state: dict,
    *,
    generation: int,
    mode: str,
    rows: int,
) -> str | None:
    """The stream twin of the CSV path's ``_record_lineage``: snapshot
    node + frozen-basis edges + generation chain. The consumed
    offset-commit edge is added by the caller once the commit exists."""
    from dct_tpu.observability import lineage as _lineage

    lin = _lineage.get_default()
    if not lin.enabled:
        return None
    basis_nid = lin.node(
        "etl_basis", content=basis, attrs={"generation": generation},
    )
    snap_nid = lin.node(
        "dataset_snapshot", path=parquet_dir,
        attrs={"generation": generation, "mode": mode, "rows": rows},
    )
    lin.edge("consumed", snap_nid, basis_nid)
    lin.edge("consumed", snap_nid, prev_state.get("lineage_node"))
    return snap_nid


def _link_commit(snap_nid: str | None, commit_nid: str | None) -> None:
    from dct_tpu.observability import lineage as _lineage

    _lineage.get_default().edge("produced", commit_nid, snap_nid)


def _publish_snapshot_swap(
    parquet_dir: str, part_name: str, out_cols: dict
) -> None:
    """Full-(re)build publish: stage the snapshot in a tmp build dir,
    then swap — the CSV rebuild's two-rename pattern, so a concurrent
    reader never observes a half-written directory."""
    tmp_build = f"{parquet_dir}.build.{os.getpid()}"
    if os.path.isdir(tmp_build):
        shutil.rmtree(tmp_build)
    os.makedirs(tmp_build)
    _publish_part(tmp_build, part_name, out_cols)
    # Spark-parity commit marker (jobs/preprocess.py writes _SUCCESS).
    open(os.path.join(tmp_build, "_SUCCESS"), "w").close()
    trash_dir = f"{parquet_dir}.old.{os.getpid()}"
    if os.path.isdir(trash_dir):
        shutil.rmtree(trash_dir)
    if os.path.isdir(parquet_dir):
        os.rename(parquet_dir, trash_dir)
    os.rename(tmp_build, parquet_dir)
    if os.path.isdir(trash_dir):
        shutil.rmtree(trash_dir)


def stream_etl_pass(
    consumer: ConsumerGroup,
    output_dir: str,
    *,
    feature_cols: list[str] | None = None,
    label_col: str = "Rain",
    positive_label: str = "rain",
    max_records: int = 8192,
    parquet_name: str = "data.parquet",
    records: list[tuple[int, int, dict]] | None = None,
    emit=None,
    clock=time.time,
) -> dict | None:
    """One exactly-once pass; returns the published ``etl_state`` dict
    when a generation landed, None when the log had nothing new.
    ``records`` lets a prefetcher hand over an already-polled span
    (its offsets must continue the committed vector — the prefetcher
    guarantees this by construction)."""
    feature_cols = feature_cols or DEFAULT_FEATURES
    parquet_dir = os.path.join(output_dir, parquet_name)
    os.makedirs(output_dir, exist_ok=True)

    commit = read_commit(consumer.log.offsets_dir, consumer.group)
    state = _heal_state_from_commit(output_dir, commit)
    committed = consumer.seek_committed()
    _remove_orphan_parts(parquet_dir, sum(committed), emit=emit)

    if records is not None:
        # A staged span is only usable if it CONTINUES the committed
        # vector (a commit may have landed between staging and now —
        # or the stager may have been seeded before a replay).
        first: dict[int, int] = {}
        for k, off, _rec in records:
            first[k] = min(first.get(k, off), off)
        if any(first[k] != committed[k] for k in first):
            records = None
    if records is None:
        records = consumer.poll(max_records)
    if not records:
        return None
    new_offsets = list(committed)
    for k, off, _rec in records:
        new_offsets[k] = max(new_offsets[k], off + 1)
    start, end = sum(committed), sum(new_offsets)
    rows = [r for _k, _off, r in records]
    stamps = [
        r[TS_KEY] for r in rows if isinstance(r.get(TS_KEY), (int, float))
    ]
    arrival_ts = max(stamps) if stamps else clock()

    basis = state.get("norm_basis") or {}
    prev_accum = state.get("accum") or {}
    fresh_basis = (
        set(basis) != set(feature_cols)
        or set(prev_accum.get("features") or {}) != set(feature_cols)
    )
    table = _records_table(rows, feature_cols, label_col)

    if fresh_basis:
        # First pass (or schema change): reference full-run semantics —
        # the basis IS this chunk's stats, snapshot swap-published.
        out_cols, moments, basis, labels = _transform_columns(
            table, feature_cols, label_col, positive_label
        )
        accum = _accum_from(moments, labels)
        mode, parts = "stream_full", 1
        rows_delta = int(len(labels))
        prev_stats = read_previous_stats(output_dir)
        _publish_snapshot_swap(
            parquet_dir, _part_name(start, end), out_cols
        )
    else:
        out_cols, delta_moments, _, delta_labels = _transform_columns(
            table, feature_cols, label_col, positive_label, basis=basis
        )
        merged = {
            name: _chan_merge(
                prev_accum["features"][name], delta_moments[name]
            )
            for name in feature_cols
        }
        merged_stats = {n: _moments_stats(m) for n, m in merged.items()}
        if _basis_stale(basis, merged_stats, _rebuild_tolerance()):
            return _stream_full_rebuild(
                consumer, output_dir, parquet_dir, state, new_offsets,
                feature_cols, label_col, positive_label,
                arrival_ts=arrival_ts, emit=emit, clock=clock,
            )
        accum = {
            "features": merged,
            "label_pos": int(prev_accum["label_pos"])
            + int(delta_labels.sum()),
            "rows": int(prev_accum["rows"]) + int(len(delta_labels)),
        }
        mode = "stream"
        parts = int(state.get("parts") or 1) + 1
        rows_delta = int(len(delta_labels))
        prev_stats = read_previous_stats(output_dir)
        # Ordering: part BEFORE stats/commit/state, so a reader that
        # saw generation N can always load generation N's rows.
        _publish_part(parquet_dir, _part_name(start, end), out_cols)

    stats = _stats_from_accum(accum)
    persist_stats_and_drift(output_dir, stats, prev_stats)
    generation = int(state.get("generation") or 0) + 1
    snap_nid = _record_stream_lineage(
        parquet_dir, basis, state,
        generation=generation, mode=mode, rows=stats["rows"],
    )
    new_state = {
        "version": ETL_STATE_VERSION,
        "generation": generation,
        "mode": mode,
        "arrival_ts": arrival_ts,
        "parts": parts,
        "rows": stats["rows"],
        "rows_delta": rows_delta,
        "norm_basis": basis,
        "accum": accum,
        "stream_offsets": new_offsets,
        "lineage_node": snap_nid,
    }
    # THE durability point: part + stats are on disk, now the offsets
    # (and the state payload) become the committed truth.
    commit_rec = consumer.commit(
        new_offsets, watermark_ts=arrival_ts, meta=new_state,
    )
    _link_commit(snap_nid, commit_rec.get("lineage_node"))
    _write_etl_state(output_dir, new_state)
    return new_state


def _stream_full_rebuild(
    consumer: ConsumerGroup,
    output_dir: str,
    parquet_dir: str,
    state: dict,
    upto: list[int],
    feature_cols: list[str],
    label_col: str,
    positive_label: str,
    *,
    arrival_ts: float,
    emit=None,
    clock=time.time,
) -> dict:
    """Basis went stale: re-read the WHOLE log up to the polled vector
    and republish the snapshot under a fresh basis (atomic swap)."""
    all_records = _read_all_records(consumer, upto)
    rows = [r for _k, _off, r in all_records]
    table = _records_table(rows, feature_cols, label_col)
    out_cols, moments, basis, labels = _transform_columns(
        table, feature_cols, label_col, positive_label
    )
    accum = _accum_from(moments, labels)
    stats = _stats_from_accum(accum)
    prev_stats = read_previous_stats(output_dir)
    _publish_snapshot_swap(parquet_dir, _part_name(0, sum(upto)), out_cols)
    persist_stats_and_drift(output_dir, stats, prev_stats)
    generation = int(state.get("generation") or 0) + 1
    if emit is not None:
        emit(
            "stream", "stream.rebuild",
            generation=generation, rows=stats["rows"],
            reason="basis_stale",
        )
    snap_nid = _record_stream_lineage(
        parquet_dir, basis, state,
        generation=generation, mode="stream_full", rows=stats["rows"],
    )
    new_state = {
        "version": ETL_STATE_VERSION,
        "generation": generation,
        "mode": "stream_full",
        "arrival_ts": arrival_ts,
        "parts": 1,
        "rows": stats["rows"],
        "rows_delta": int(len(labels)),
        "norm_basis": basis,
        "accum": accum,
        "stream_offsets": list(upto),
        "lineage_node": snap_nid,
    }
    commit_rec = consumer.commit(
        list(upto), watermark_ts=arrival_ts, meta=new_state,
    )
    _link_commit(snap_nid, commit_rec.get("lineage_node"))
    _write_etl_state(output_dir, new_state)
    return new_state
