"""Process-aware logging.

The reference's observability is bare prints with PYTHONUNBUFFERED=1
(Dockerfile.pytorch:26) collected by Airflow task logs. Here every record is
prefixed with the JAX process index so interleaved multi-host logs from the
orchestrator's join (dags/2_pytorch_training.py:62-75 analog) stay legible.
"""

from __future__ import annotations

import logging
import sys


def get_logger(name: str = "dct_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stdout)
        try:
            import jax

            rank = jax.process_index()
        except Exception:
            rank = 0
        handler.setFormatter(
            logging.Formatter(
                f"[%(asctime)s rank={rank}] %(levelname)s %(name)s: %(message)s"
            )
        )
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger
