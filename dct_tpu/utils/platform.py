"""Backend liveness probing: never let a dead accelerator hang the job.

JAX backend initialization is a blocking call with no timeout: if the TPU
runtime's control plane is unreachable (dead tunnel, stale session claim,
relay wedged by a killed process), ``jax.devices()`` blocks forever inside
PJRT client creation — there is no in-process way to interrupt it. The
reference pipeline has the same class of failure (a stale rank holding the
gloo rendezvous port) and guards it with a pre-launch zombie purge
(dags/2_pytorch_training.py:29-38, SURVEY §5.2); the TPU-native analog is
this **subprocess probe**: initialize the default backend in a disposable
child with a hard timeout, and if it does not come up, fall back to CPU in
the parent *before* any backend init, so benches/health checks always
complete and report rather than hanging their orchestrator.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

# The child honors JAX_PLATFORMS env over any sitecustomize config clobber
# (mirroring ensure_live_backend's own policy) so it initializes exactly the
# backend the parent would.
_PROBE_SRC = (
    "import os, jax; w = os.environ.get('JAX_PLATFORMS'); "
    "jax.config.update('jax_platforms', w) if (w and jax.config.jax_platforms != w) else None; "
    "jax.devices(); print(jax.default_backend())"
)


def probe_default_backend(timeout: float = 150.0) -> str | None:
    """Initialize the default JAX backend in a child process.

    Returns the backend name on success, None if init hangs/fails.
    """
    try:
        res = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None
    if res.returncode != 0:
        return None
    out = res.stdout.strip().splitlines()
    return out[-1] if out else None


def ensure_live_backend(
    timeout: float | None = None, retries: int | None = None
) -> str:
    """Make sure this process's first backend init cannot hang.

    - An explicit ``JAX_PLATFORMS`` env var wins over any sitecustomize
      config clobber (restored into jax config here).
    - A cpu-only selection needs no probe.
    - Anything else — including the empty config, where JAX auto-detects
      an accelerator — is probed in a subprocess; on failure this process
      (and children, via env) is pinned to CPU.

    A transiently wedged control plane (relay recovering from a killed
    client) often comes back within seconds, so a probe child that FAILS
    FAST (crash, connection refused) is retried up to ``retries`` times
    (``DCT_BACKEND_PROBE_RETRIES``, default 3) with exponential backoff.
    Every attempt gets the FULL remaining ``timeout`` budget
    (``DCT_BACKEND_PROBE_TIMEOUT`` seconds, 150 if unset) — splitting it
    would shrink the tolerated init latency, and a child killed at its
    timeout restarts init from scratch on retry, so a smaller window can
    never succeed where the bigger one didn't. Net: slow-but-healthy init
    keeps the old single-probe tolerance; fast failures get retries the
    old code lacked (VERDICT r2 item 1).

    Must be called before any jax backend initializes. Returns the platform
    that will be used ("cpu" or the probed default, e.g. "tpu").
    """
    import jax

    if timeout is None:
        timeout = float(os.environ.get("DCT_BACKEND_PROBE_TIMEOUT", "150"))
    if retries is None:
        retries = max(1, int(os.environ.get("DCT_BACKEND_PROBE_RETRIES", "3")))

    want = os.environ.get("JAX_PLATFORMS")
    if want and jax.config.jax_platforms != want:
        jax.config.update("jax_platforms", want)
    platforms = want or jax.config.jax_platforms or ""
    if platforms == "cpu":
        return "cpu"

    backoff = 2.0
    deadline = time.monotonic() + timeout
    attempts = 0
    for attempt in range(retries):
        remaining = timeout if attempt == 0 else deadline - time.monotonic()
        if remaining <= 0:
            break
        attempts += 1
        backend = probe_default_backend(timeout=remaining)
        if backend is not None:
            if attempt:
                sys.stderr.write(
                    f"[dct_tpu] backend probe succeeded on attempt "
                    f"{attempt + 1}/{retries}\n"
                )
            return backend
        if attempt == retries - 1:
            break
        if time.monotonic() + backoff >= deadline:
            # No room to wait out a recovering relay — an immediate
            # re-probe against the same wedged control plane is doomed,
            # so stop rather than burn subprocess spawns.
            break
        sys.stderr.write(
            f"[dct_tpu] backend probe attempt {attempt + 1}/{retries} "
            f"failed — retrying in {backoff:.0f}s\n"
        )
        time.sleep(backoff)
        backoff *= 2

    elapsed = time.monotonic() - (deadline - timeout)
    sys.stderr.write(
        f"[dct_tpu] default backend ({(platforms or 'auto')!r}) failed to "
        f"initialize: {attempts} attempt(s) over {elapsed:.0f}s "
        f"(budget {timeout:.0f}s) — falling back to CPU\n"
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    return "cpu"
