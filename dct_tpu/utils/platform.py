"""Backend liveness probing: never let a dead accelerator hang the job.

JAX backend initialization is a blocking call with no timeout: if the TPU
runtime's control plane is unreachable (dead tunnel, stale session claim,
relay wedged by a killed process), ``jax.devices()`` blocks forever inside
PJRT client creation — there is no in-process way to interrupt it. The
reference pipeline has the same class of failure (a stale rank holding the
gloo rendezvous port) and guards it with a pre-launch zombie purge
(dags/2_pytorch_training.py:29-38, SURVEY §5.2); the TPU-native analog is
this **subprocess probe**: initialize the default backend in a disposable
child with a hard timeout, and if it does not come up, fall back to CPU in
the parent *before* any backend init, so benches/health checks always
complete and report rather than hanging their orchestrator.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

# The child honors JAX_PLATFORMS env over any sitecustomize config clobber
# (mirroring ensure_live_backend's own policy) so it initializes exactly the
# backend the parent would.
_PROBE_SRC = (
    "import os, jax; w = os.environ.get('JAX_PLATFORMS'); "
    "jax.config.update('jax_platforms', w) if (w and jax.config.jax_platforms != w) else None; "
    "jax.devices(); print(jax.default_backend())"
)


def probe_default_backend(timeout: float = 150.0) -> str | None:
    """Initialize the default JAX backend in a child process.

    Returns the backend name on success, None if init hangs/fails.
    """
    try:
        res = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None
    if res.returncode != 0:
        return None
    out = res.stdout.strip().splitlines()
    return out[-1] if out else None


# Diagnostics of the most recent ensure_live_backend() call, for callers
# that record their platform (bench.py stamps this into its JSON line so a
# "platform": "cpu" record is self-explaining — VERDICT r3 item 1: two
# rounds of CPU records gave no evidence the probe even ran).
LAST_PROBE: dict = {}


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at a shared on-disk dir.

    Over the tunneled TPU a single scan-16 train program costs ~5-7 min
    to compile, and a live relay window runs the SAME programs in
    multiple processes back to back (campaign, then the insurance
    bench, then possibly the driver's own bench) — without a persistent
    cache every process pays every compile again. Called by the long-
    running measurement entry points. ``DCT_JAX_CACHE``: ``off`` (and
    the usual falsy spellings) disables; the default ``auto`` enables on
    the TPU backend ONLY and silently returns None elsewhere (XLA:CPU
    AOT entries are machine-feature-pinned — a mismatched load can
    SIGILL); ``force`` enables on any backend.

    Returns the cache dir in use, or None when disabled/unavailable.
    """
    mode = os.environ.get("DCT_JAX_CACHE", "auto").strip().lower()
    if mode in ("0", "false", "no", "off", "disable", "none"):
        return None
    try:
        import jax

        backend = jax.default_backend()
    except Exception:  # noqa: BLE001
        return None
    if mode != "force" and backend != "tpu":
        # TPU-only by default: the cache exists for the tunnel's ~5-7 min
        # compiles. XLA:CPU AOT entries are machine-feature-pinned and a
        # mismatched load warns it "could lead to execution errors such
        # as SIGILL" (observed on this rig) — a cache is never worth a
        # possibly-crashing measurement process.
        return None
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    path = (
        cache_dir
        or os.environ.get("DCT_JAX_CACHE_DIR")
        or os.path.join(repo_root, ".jax_cache")
    )
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # Cache every compile that took >= 2 s: dispatch-tier programs
        # are cheap to rebuild, but everything the tunnel makes slow
        # (and every CPU scan program behind it) is worth keeping.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # noqa: BLE001 — cache is an optimization,
        # never a reason to fail a measurement run
        sys.stderr.write(f"[dct_tpu] compilation cache unavailable: {e}\n")
        return None
    return path


class BackendRequiredError(RuntimeError):
    """Raised under DCT_REQUIRE_TPU=1 when no accelerator came up."""


def ensure_live_backend(
    timeout: float | None = None,
    retries: int | None = None,
    budget: float | None = None,
) -> str:
    """Make sure this process's first backend init cannot hang.

    - An explicit ``JAX_PLATFORMS`` env var wins over any sitecustomize
      config clobber (restored into jax config here).
    - A cpu-only selection needs no probe.
    - Anything else — including the empty config, where JAX auto-detects
      an accelerator — is probed in a subprocess; on failure this process
      (and children, via env) is pinned to CPU.

    Two time knobs (VERDICT r3 item 1 — round 3 surrendered to CPU after
    150 s while its bench still had 1350 s of budget left):

    - ``timeout`` (``DCT_BACKEND_PROBE_TIMEOUT``, 150 s): per-attempt cap.
      A healthy-but-slow init finishes well inside it; a child killed at
      its cap restarts init from scratch, so a longer single window only
      helps init latency, while more *attempts* catch a relay that
      recovers mid-wait.
    - ``budget`` (``DCT_BACKEND_PROBE_BUDGET``, defaults to ``timeout``):
      total re-probe window. Attempts repeat — full-cap hangs back-to-back,
      fast failures with exponential backoff — until it is exhausted or
      ``retries`` caps them. Escalating callers (the bench) pass half
      their own deadline here.

    ``DCT_REQUIRE_TPU=1`` refuses the CPU fallback: exhausting the budget
    raises :class:`BackendRequiredError` instead, so a driver run that
    must produce an on-chip record exits nonzero with the probe log rather
    than silently recording CPU numbers.

    Must be called before any jax backend initializes. Returns the platform
    that will be used ("cpu" or the probed default, e.g. "tpu").
    """
    import jax

    if timeout is None:
        timeout = float(os.environ.get("DCT_BACKEND_PROBE_TIMEOUT", "150"))
    if budget is None:
        budget = float(
            os.environ.get("DCT_BACKEND_PROBE_BUDGET", str(timeout))
        )
    # A caller's budget is a hard wall-time promise: shrink the per-attempt
    # cap to fit rather than silently probing past it.
    timeout = min(timeout, budget)
    if retries is None:
        env_retries = os.environ.get("DCT_BACKEND_PROBE_RETRIES")
        if env_retries:
            retries = max(1, int(env_retries))
        else:
            # Attempts are bounded by the budget deadline, not a count:
            # both failure modes (full-cap hangs and fast failures with
            # capped backoff) must fill the whole window — a count small
            # enough for one mode surrenders the budget in the other.
            retries = 10_000
    require = os.environ.get("DCT_REQUIRE_TPU", "").strip().lower() in (
        "1", "true", "yes"
    )

    want = os.environ.get("JAX_PLATFORMS")
    if want and jax.config.jax_platforms != want:
        jax.config.update("jax_platforms", want)
    platforms = want or jax.config.jax_platforms or ""
    if platforms == "cpu":
        if require:
            raise BackendRequiredError(
                "DCT_REQUIRE_TPU=1 but JAX_PLATFORMS=cpu pins this process "
                "to CPU — unset one of them"
            )
        LAST_PROBE.clear()
        LAST_PROBE.update(
            requested="cpu", platform="cpu", attempts=0, elapsed_s=0.0,
            budget_s=0.0, fallback_reason=None,
        )
        return "cpu"

    start = time.monotonic()
    deadline = start + budget
    backoff = 2.0
    attempts = 0
    for attempt in range(retries):
        remaining = timeout if attempt == 0 else deadline - time.monotonic()
        if remaining <= 0:
            break
        attempts += 1
        probe_t0 = time.monotonic()
        backend = probe_default_backend(timeout=min(timeout, remaining))
        probe_dt = time.monotonic() - probe_t0
        if backend is not None:
            if attempt:
                sys.stderr.write(
                    f"[dct_tpu] backend probe succeeded on attempt "
                    f"{attempt + 1}/{retries}\n"
                )
            LAST_PROBE.clear()
            LAST_PROBE.update(
                requested=platforms or "auto", platform=backend,
                attempts=attempts,
                elapsed_s=round(time.monotonic() - start, 1),
                budget_s=budget, fallback_reason=None,
            )
            return backend
        if attempt == retries - 1:
            break
        if probe_dt >= min(timeout, remaining) * 0.9:
            # The child burned its full window hanging in backend init —
            # the relay may recover any moment, so re-probe immediately;
            # sleeping on top of a full-cap hang only wastes budget.
            wait = 0.0
        else:
            # Cap the backoff: uncapped doubling would burn an escalated
            # budget in sleeps (2+4+...+512 s) after a dozen fast
            # failures; 30 s keeps re-probing a restarting relay at a
            # useful cadence for the whole window.
            wait = min(backoff, 30.0)
            backoff *= 2
        if time.monotonic() + wait >= deadline:
            # No room to wait out a recovering relay — an immediate
            # re-probe against the same wedged control plane is doomed,
            # so stop rather than burn subprocess spawns.
            break
        if wait:
            sys.stderr.write(
                f"[dct_tpu] backend probe attempt {attempt + 1}/{retries} "
                f"failed — retrying in {wait:.0f}s\n"
            )
            time.sleep(wait)

    elapsed = time.monotonic() - start
    reason = (
        f"backend {(platforms or 'auto')!r} failed to initialize: "
        f"{attempts} probe attempt(s) over {elapsed:.0f}s "
        f"(budget {budget:.0f}s, per-attempt cap {timeout:.0f}s)"
    )
    LAST_PROBE.clear()
    LAST_PROBE.update(
        requested=platforms or "auto", platform="cpu", attempts=attempts,
        elapsed_s=round(elapsed, 1), budget_s=budget, fallback_reason=reason,
    )
    if require:
        raise BackendRequiredError(
            f"DCT_REQUIRE_TPU=1 and no accelerator came up — {reason}"
        )
    sys.stderr.write(f"[dct_tpu] {reason} — falling back to CPU\n")
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    return "cpu"
