"""Backend liveness probing: never let a dead accelerator hang the job.

JAX backend initialization is a blocking call with no timeout: if the TPU
runtime's control plane is unreachable (dead tunnel, stale session claim,
relay wedged by a killed process), ``jax.devices()`` blocks forever inside
PJRT client creation — there is no in-process way to interrupt it. The
reference pipeline has the same class of failure (a stale rank holding the
gloo rendezvous port) and guards it with a pre-launch zombie purge
(dags/2_pytorch_training.py:29-38, SURVEY §5.2); the TPU-native analog is
this **subprocess probe**: initialize the default backend in a disposable
child with a hard timeout, and if it does not come up, fall back to CPU in
the parent *before* any backend init, so benches/health checks always
complete and report rather than hanging their orchestrator.
"""

from __future__ import annotations

import os
import subprocess
import sys

# The child honors JAX_PLATFORMS env over any sitecustomize config clobber
# (mirroring ensure_live_backend's own policy) so it initializes exactly the
# backend the parent would.
_PROBE_SRC = (
    "import os, jax; w = os.environ.get('JAX_PLATFORMS'); "
    "jax.config.update('jax_platforms', w) if (w and jax.config.jax_platforms != w) else None; "
    "jax.devices(); print(jax.default_backend())"
)


def probe_default_backend(timeout: float = 150.0) -> str | None:
    """Initialize the default JAX backend in a child process.

    Returns the backend name on success, None if init hangs/fails.
    """
    try:
        res = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None
    if res.returncode != 0:
        return None
    out = res.stdout.strip().splitlines()
    return out[-1] if out else None


def ensure_live_backend(timeout: float | None = None) -> str:
    """Make sure this process's first backend init cannot hang.

    - An explicit ``JAX_PLATFORMS`` env var wins over any sitecustomize
      config clobber (restored into jax config here).
    - A cpu-only selection needs no probe.
    - Anything else — including the empty config, where JAX auto-detects
      an accelerator — is probed in a subprocess; on failure this process
      (and children, via env) is pinned to CPU.

    Must be called before any jax backend initializes. Returns the platform
    that will be used ("cpu" or the probed default, e.g. "tpu").
    ``timeout`` defaults to the ``DCT_BACKEND_PROBE_TIMEOUT`` env var
    (seconds, 150 if unset) so every caller honors the knob.
    """
    import jax

    if timeout is None:
        timeout = float(os.environ.get("DCT_BACKEND_PROBE_TIMEOUT", "150"))

    want = os.environ.get("JAX_PLATFORMS")
    if want and jax.config.jax_platforms != want:
        jax.config.update("jax_platforms", want)
    platforms = want or jax.config.jax_platforms or ""
    if platforms == "cpu":
        return "cpu"

    backend = probe_default_backend(timeout=timeout)
    if backend is not None:
        return backend

    sys.stderr.write(
        f"[dct_tpu] default backend ({(platforms or 'auto')!r}) failed to "
        f"initialize within {timeout:.0f}s — falling back to CPU\n"
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    return "cpu"
