"""Tracing / profiling subsystem.

The reference has NO tracing or profiling (SURVEY §5.1): its only
observability knobs are ``log_every_n_steps=5`` cadence control
(jobs/train_lightning_ddp.py:139) and stdout prints; TensorBoard is
installed in the trainer image (Dockerfile.pytorch:16) and a DAG task looks
for a logs directory (dags/pipeline.py:229-240) but nothing ever writes it.
This module fills that gap TPU-natively:

- :class:`Profiler` — a coordinator-gated window around ``jax.profiler``
  device tracing. The trace (XLA ops, fusion boundaries, HBM transfers,
  ICI collectives) lands in a TensorBoard-compatible ``plugins/profile``
  directory, satisfying the DAG's TensorBoard-logs check with real content.
- :class:`EpochTimer` — wall-clock + throughput accounting per epoch
  (samples/sec and samples/sec/chip, the BASELINE.md north-star metric),
  ready to be logged as tracking metrics next to val_loss.
- :func:`annotate` — host-side named spans (``jax.profiler.TraceAnnotation``)
  so batch assembly and H2D staging show up on the trace timeline alongside
  device work.

Profiling is a window, not a mode: tracing every step of a long run would
produce gigabytes and perturb the steady state, so the profiler arms itself
for one configured epoch and disarms after.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field


def annotate(name: str):
    """Named host span that appears on the profiler timeline."""
    import jax.profiler

    return jax.profiler.TraceAnnotation(name)


class Profiler:
    """Start/stop ``jax.profiler`` tracing around one epoch window.

    Only the coordinator process traces (every process tracing would write
    world_size copies; the device timeline of process 0 is representative
    for SPMD programs). Safe to call when disabled — all methods no-op.
    """

    def __init__(self, trace_dir: str, *, enabled: bool, epoch: int,
                 coordinator: bool = True):
        self.trace_dir = trace_dir
        self.enabled = bool(enabled) and coordinator
        self.epoch = int(epoch)
        self._active = False

    def maybe_start(self, epoch: int) -> None:
        if not self.enabled or self._active or epoch != self.epoch:
            return
        # One jax.profiler session per process: the planned window
        # shares the flight recorder's gate (observability/capture.py).
        # If an on-demand capture is mid-flight when the target epoch
        # arrives, the planned trace is SKIPPED with a note — a second
        # start_trace would raise and fail the run.
        from dct_tpu.observability.capture import _SESSION_LOCK

        if not _SESSION_LOCK.acquire(blocking=False):
            import sys

            print(
                f"[dct_tpu] planned profile of epoch {self.epoch} "
                "skipped: an on-demand capture is already running",
                file=sys.stderr, flush=True,
            )
            return
        try:
            import jax.profiler

            os.makedirs(self.trace_dir, exist_ok=True)
            jax.profiler.start_trace(self.trace_dir)
        except Exception:
            _SESSION_LOCK.release()
            raise
        self._active = True

    def maybe_stop(self, epoch: int) -> None:
        if not self._active or epoch != self.epoch:
            return
        from dct_tpu.observability.capture import _SESSION_LOCK

        import jax.profiler

        try:
            jax.profiler.stop_trace()
        finally:
            self._active = False
            _SESSION_LOCK.release()

    def maybe_start_span(self, epoch: int, k: int) -> None:
        """Span form for epoch-chunked loops: the target epoch fires the
        trace if it falls anywhere in [epoch, epoch + k) — with K epochs
        per dispatch the loop never visits it exactly (the trace then
        covers the whole chunk's dispatch; the target's timeline is
        inside it)."""
        if epoch <= self.epoch < epoch + k:
            self.maybe_start(self.epoch)

    def maybe_stop_span(self, epoch: int, k: int) -> None:
        if epoch <= self.epoch < epoch + k:
            self.maybe_stop(self.epoch)

    def close(self) -> None:
        """Stop tracing unconditionally (crash-path hygiene: an abandoned
        trace session would corrupt the output directory)."""
        if self._active:
            from dct_tpu.observability.capture import _SESSION_LOCK

            import jax.profiler

            try:
                jax.profiler.stop_trace()
            finally:
                self._active = False
                _SESSION_LOCK.release()


def chip_peak_flops() -> float | None:
    """Best-effort bf16 peak FLOPs/sec per chip from the device kind
    (None when unknown). Override with DCT_PEAK_TFLOPS."""
    import jax

    env = os.environ.get("DCT_PEAK_TFLOPS")
    if env:
        return float(env) * 1e12
    kind = jax.devices()[0].device_kind.lower()
    for pat, peak_t in (
        ("v6", 918.0), ("v5p", 459.0), ("v5 lite", 197.0), ("v5e", 197.0),
        ("v4", 275.0), ("v3", 123.0), ("v2", 45.0),
    ):
        if pat in kind:
            return peak_t * 1e12
    return None


def transformer_train_flops(
    *, d_model: int, d_ff: int, seq_len: int, n_heads: int, n_layers: int,
    input_dim: int, batch: int, num_classes: int = 2,
) -> float:
    """Analytic matmul FLOPs for ONE transformer optimizer step
    (fwd + bwd ~ 3x fwd): projection/FFN GEMMs at 2*params*tokens plus
    the attention score/value einsums (4*B*H*S^2*Dh per layer);
    elementwise work excluded. Used for MFU = this / step_time / peak."""
    tokens = batch * seq_len
    proj_params = (
        n_layers * (4 * d_model * d_model + 2 * d_model * d_ff)
        + input_dim * d_model + d_model * num_classes
    )
    fwd = (
        2.0 * proj_params * tokens
        + 4.0 * batch * n_heads * seq_len * seq_len
        * (d_model // n_heads) * n_layers
    )
    return 3.0 * fwd


@dataclass
class EpochStats:
    epoch: int
    seconds: float
    samples: int
    samples_per_sec: float
    samples_per_sec_per_chip: float
    # Model-FLOPs utilization (achieved/peak); None when the analytic
    # FLOPs or the chip peak are unknown (e.g. MLP family, CPU rig).
    mfu: float | None = None


@dataclass
class EpochTimer:
    """Accumulates per-epoch wall time and throughput.

    ``n_chips`` divides throughput into the per-chip north-star metric
    (BASELINE.md): honest accounting means the clock includes host batch
    assembly and H2D staging, not just device execution.
    """

    n_chips: int = 1
    # Analytic train FLOPs per SAMPLE (transformer_train_flops(batch=1));
    # with the chip peak this turns throughput into per-epoch MFU.
    flops_per_sample: float | None = None
    peak_flops: float | None = None
    # Optional goodput ledger (observability.goodput.GoodputLedger): each
    # stop() feeds the epoch's wall seconds to the ledger's per-epoch
    # marks, so goodput reports share the timer's clock windows.
    ledger: object | None = None
    history: list = field(default_factory=list)
    _t0: float = 0.0

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(
        self, epoch: int, samples: int, eval_samples: int = 0
    ) -> EpochStats:
        """``samples`` = TRAIN samples; ``eval_samples`` = validation
        samples whose forward pass ran inside the timed window (the
        fused train+eval epoch program). samples_per_sec stays
        train-samples over the full epoch wall time — the reference's
        per-epoch cadence also includes validation — while MFU credits
        the eval forwards (1/3 of a train sample's FLOPs) so utilization
        is not understated by work the denominator paid for."""
        dt = time.perf_counter() - self._t0
        sps = samples / dt if dt > 0 else 0.0
        mfu = None
        if self.flops_per_sample and self.peak_flops and dt > 0:
            achieved = (
                (samples + eval_samples / 3.0) * self.flops_per_sample / dt
            )
            mfu = achieved / max(self.n_chips, 1) / self.peak_flops
        stats = EpochStats(
            epoch=epoch,
            seconds=dt,
            samples=samples,
            samples_per_sec=sps,
            samples_per_sec_per_chip=sps / max(self.n_chips, 1),
            mfu=mfu,
        )
        self.history.append(stats)
        if self.ledger is not None:
            self.ledger.note_epoch(epoch, dt)
        return stats

    @property
    def total_seconds(self) -> float:
        return sum(s.seconds for s in self.history)

    @property
    def total_samples(self) -> int:
        return sum(s.samples for s in self.history)

    @property
    def samples_per_sec(self) -> float:
        t = self.total_seconds
        return self.total_samples / t if t > 0 else 0.0
