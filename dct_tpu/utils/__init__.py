from dct_tpu.utils.logging import get_logger  # noqa: F401
