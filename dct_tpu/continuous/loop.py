"""The always-on loop: overlapped ETL / train / gate / deploy.

``AlwaysOnLoop.run()`` drives three concurrent actors over shared,
atomically-published artifacts:

- the TRAIN PUMP (this thread): back-to-back rounds of
  ``epochs_per_round`` epochs, each EXTENDING one optimizer trajectory
  (``resume`` semantics — exactly the serial trainer's continuation
  path, so per-step semantics are bit-identical by construction). In
  ``supervised`` mode every round runs under the PR 3 supervisor
  (crash/hang healing, compile-cache continuity); ``inline`` runs
  Trainer.fit in-process (benches/tests).
- the INGEST WATCHER (daemon thread): digest-polls the raw staging CSV
  and feeds the incremental ETL, so a fresh generation is published
  while training computes — the next round picks it up with zero serial
  ETL wait.
- the PROMOTION EVALUATOR (daemon thread): watches the deploy-tier best
  checkpoint and walks each new one through gate + rollout against the
  live champion — promotion happens MID-RUN, overlapped with training.

Freshness: data-arrival -> deployed-model latency is bounded by stage
latencies (round + gate + rollout), not by the episodic cycle sum. The
``cycle_freshness`` bench leg measures both against
:func:`run_episodic_cycle`, the serial comparator built from the SAME
primitives run strictly in sequence.

Shutdown: ``request_stop()`` (or SIGTERM via ``jobs/loop.py``) finishes
the round in flight — mid-fit, the trainer's own PreemptionGuard turns
the signal into a durable resume snapshot — then drains both threads,
runs one final evaluator sweep over whatever the last round published,
and emits ``loop.stop``. A relaunch resumes the trajectory and the
deployed champion unchanged.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import threading
import time

from dct_tpu.config import RunConfig

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))


def _loop_event_log(cfg: RunConfig, run_id: str):
    from dct_tpu.observability.events import EventLog

    path = (
        os.path.join(cfg.obs.events_dir, "events.jsonl")
        if cfg.obs.enabled and cfg.obs.events_dir
        else None
    )
    return EventLog(path, run_id=run_id)


def _round_config(cfg: RunConfig, epochs: int) -> RunConfig:
    """One training round's config: the loop's epoch quantum with
    resume ALWAYS on (every round extends the same trajectory)."""
    return dataclasses.replace(
        cfg,
        train=dataclasses.replace(cfg.train, epochs=epochs, resume=True),
    )


class AlwaysOnLoop:
    """The loop runtime. Construct with a full :class:`RunConfig`
    (``cfg.loop`` carries the loop knobs); ``client`` defaults to a
    :class:`~dct_tpu.deploy.local.LocalEndpointClient` persisted beside
    the packages dir so a relaunched loop sees its deployed champion."""

    def __init__(
        self,
        cfg: RunConfig,
        *,
        client=None,
        clock=time.time,
        sleep_fn=time.sleep,
        on_promotion=None,
        on_round=None,
        round_gate=None,
        extra_round_env=None,
        launcher_kwargs=None,
    ):
        from dct_tpu.observability.events import current_run_id

        self.cfg = cfg
        self.loop_cfg = cfg.loop
        self._clock = clock
        self._sleep = sleep_fn
        self._on_round = on_round
        # Multi-tenant hooks (dct_tpu.scheduler; docs/SCHEDULER.md):
        # ``round_gate`` is consulted before EVERY round — it blocks
        # until the scheduler grants this loop a round lease (False =
        # the session is draining); ``extra_round_env`` rides into every
        # supervised round's child ranks (per-tenant DCT_* overrides —
        # family, fault drills, world size); ``launcher_kwargs`` lets
        # each tenant's supervised worlds use their own coordinator
        # port. All default to the single-tenant behavior.
        self._round_gate = round_gate
        self._extra_round_env = dict(extra_round_env or {})
        self._launcher_kwargs = dict(launcher_kwargs or {})
        # Scheduler-initiated graceful ROUND preemption: set by
        # preempt_round(); the in-flight round checkpoints and ends
        # early, and the loop returns to the gate instead of draining.
        self._round_preempt = threading.Event()
        self._inline_guard = None
        self.preempted_rounds = 0
        self.run_id = cfg.obs.run_id or current_run_id()
        # Every inline fit (and the checkpoint/tracking layers under it)
        # stamps the SAME run-correlation ID: one grep spans the whole
        # always-on session.
        cfg.obs.run_id = self.run_id
        self.events = _loop_event_log(cfg, self.run_id)
        if client is None:
            from dct_tpu.deploy.local import LocalEndpointClient

            os.makedirs(self.loop_cfg.packages_dir, exist_ok=True)
            client = LocalEndpointClient(
                state_path=os.path.join(
                    self.loop_cfg.packages_dir, "endpoint_state.json"
                )
            )
        self.client = client
        from dct_tpu.continuous.evaluator import PromotionEvaluator
        from dct_tpu.continuous.ingest import (
            IngestWatcher, StreamIngestWatcher,
        )

        if cfg.stream.mode == "stream":
            self.ingest = StreamIngestWatcher(
                cfg.stream, cfg.data.processed_dir,
                poll_s=cfg.stream.poll_s,
                metrics_dir=cfg.obs.metrics_dir,
                emit=self.events.emit, clock=clock,
            )
        else:
            self.ingest = IngestWatcher(
                cfg.data.raw_csv, cfg.data.processed_dir,
                poll_s=self.loop_cfg.poll_s,
                emit=self.events.emit, clock=clock,
            )
        self.evaluator = PromotionEvaluator(
            cfg.data.models_dir, self.loop_cfg.packages_dir,
            client=self.client, endpoint=self.loop_cfg.endpoint,
            processed_dir=cfg.data.processed_dir,
            soak_s=self.loop_cfg.soak_s, poll_s=self.loop_cfg.eval_poll_s,
            run_id=self.run_id, emit=self.events.emit,
            clock=clock, sleep_fn=sleep_fn,
            on_promotion=on_promotion,
        )
        self._stop = threading.Event()
        self.stop_reason: str | None = None
        self.rounds = 0
        self.round_results: list[dict] = []
        self.train_step_wall_s = 0.0
        self.train_samples_per_sec_per_chip: list[float] = []

    # -- control --------------------------------------------------------
    def request_stop(self, reason: str = "requested") -> None:
        if self.stop_reason is None:
            self.stop_reason = reason
        self._stop.set()

    @property
    def stopping(self) -> bool:
        return self._stop.is_set()

    def preempt_round(self) -> None:
        """Gracefully preempt the IN-FLIGHT round (scheduler lease
        revocation): the trainer finishes its step and makes the resume
        snapshot durable — the PR 3 preemption contract — then the loop
        returns to the round gate with the session still alive. A no-op
        when no round is running (the flag is cleared at the next round
        start)."""
        self._round_preempt.set()
        guard = self._inline_guard
        if guard is not None:
            guard.request()

    # -- training rounds ------------------------------------------------
    def _run_round_inline(self) -> dict:
        from dct_tpu.resilience.preempt import PreemptionGuard
        from dct_tpu.train.trainer import Trainer

        cfg = _round_config(self.cfg, self.loop_cfg.epochs_per_round)
        # The loop owns the round's preemption guard so preempt_round()
        # can request a graceful stop from another thread (in the main
        # thread the guard still installs the SIGTERM handler exactly
        # as a trainer-built one would).
        guard = PreemptionGuard(clock=self._clock)
        self._inline_guard = guard
        if self._round_preempt.is_set():
            guard.request()
        try:
            try:
                result = Trainer(cfg, preempt_guard=guard).fit()
            except FileNotFoundError:
                # The ingest thread's full-rebuild swap has a two-rename
                # window with no parquet dir; a round starting inside it
                # must retry, not kill the always-on session (supervised
                # mode heals the same race via the PR 3 relauncher).
                self._sleep(0.2)
                result = Trainer(cfg, preempt_guard=guard).fit()
        finally:
            self._inline_guard = None
        cats = (result.goodput or {}).get("categories") or {}
        train_step_s = float(cats.get("train_step", 0.0))
        self.train_step_wall_s += train_step_s
        if result.steady_samples_per_sec_per_chip:
            self.train_samples_per_sec_per_chip.append(
                result.steady_samples_per_sec_per_chip
            )
        return {
            "mode": "inline",
            "epochs": self.loop_cfg.epochs_per_round,
            "val_loss": result.val_loss,
            "val_acc": result.val_acc,
            # Scheduler quota accounting: useful seconds in the lease
            # (sub-ms dispatches on toy rounds — keep the precision).
            "goodput_s": round(train_step_s, 4),
        }

    def _run_round_supervised(self) -> dict:
        from dct_tpu.launch.launcher import LocalProcessLauncher

        world_size = int(
            self._extra_round_env.get("DCT_WORLD_SIZE")
            or os.environ.get("DCT_WORLD_SIZE", "1") or 1
        )
        # The child ranks rebuild RunConfig.from_env(): every path THIS
        # loop was constructed with must travel, or a programmatic
        # RunConfig would train into env-default dirs while the
        # watcher/evaluator look at the configured ones.
        env = {
            "DCT_EPOCHS": str(self.loop_cfg.epochs_per_round),
            "DCT_RESUME": "1",
            "DCT_RUN_ID": self.run_id,
            "DCT_PROCESSED_DIR": self.cfg.data.processed_dir,
            "DCT_RAW_CSV": self.cfg.data.raw_csv,
            "DCT_MODELS_DIR": self.cfg.data.models_dir,
            "DCT_EVENTS_DIR": self.cfg.obs.events_dir,
            "DCT_HEARTBEAT_DIR": self.cfg.obs.heartbeat_dir,
            # Sharded continuous training: the mesh layout and the
            # partition-rule knobs THIS loop was configured with must
            # travel into every child rank, or a programmatic RunConfig
            # would train data-parallel while the evaluator (and the
            # checkpoints it watches) expect the sharded layout — and a
            # mid-run promotion on a sharded trajectory would judge the
            # wrong model.
            "DCT_MESH_DATA": str(self.cfg.mesh.data),
            "DCT_MESH_MODEL": str(self.cfg.mesh.model),
            "DCT_MESH_SEQ": str(self.cfg.mesh.seq),
            "DCT_MESH_PIPE": str(self.cfg.mesh.pipe),
            "DCT_SHARD_OPT_STATE": (
                "1" if self.cfg.train.shard_opt_state else "0"
            ),
            "DCT_SHARD_PARAMS": "1" if self.cfg.train.shard_params else "0",
            # Stream-mode identity: the child trainer reads etl_state
            # written by THIS loop's stream ETL, and its provenance
            # stamp (stream_offsets → checkpoint meta) must name the
            # same log + group the watcher commits against.
            "DCT_INGEST_MODE": self.cfg.stream.mode,
            "DCT_STREAM_DIR": self.cfg.stream.dir,
            "DCT_STREAM_TOPIC": self.cfg.stream.topic,
            "DCT_STREAM_GROUP": self.cfg.stream.group,
        }
        # Env-only knob: an operator's rule overrides ride along when
        # set (os.environ inheritance covers the CLI path; this covers
        # a launcher given a scrubbed env).
        if os.environ.get("DCT_SHARD_RULES"):
            env["DCT_SHARD_RULES"] = os.environ["DCT_SHARD_RULES"]
        # Per-tenant overrides (scheduler mode) ride UNDER the loop's
        # own cfg-derived keys: the tenant env shaped this loop's cfg in
        # the first place, and the cfg is the operative round contract.
        if self._extra_round_env:
            env = {**self._extra_round_env, **env}
        launcher = LocalProcessLauncher(**self._launcher_kwargs)
        res = launcher.supervise(
            [sys.executable, os.path.join(_REPO_ROOT, "jobs", "train_tpu.py")],
            world_size=world_size,
            env=env,
            max_restarts=self.cfg.resilience.max_restarts,
            backoff_s=self.cfg.resilience.restart_backoff_s,
            backoff_factor=self.cfg.resilience.restart_backoff_factor,
            jitter=self.cfg.resilience.restart_jitter,
            preempt_event=self._round_preempt,
        )
        attempts = getattr(res, "attempts", None)
        if res.restarts and "DCT_FAULT_SPEC" in self._extra_round_env:
            from dct_tpu.resilience.faults import FAULT_CRASH_EXIT

            # Per-session drill semantics, one level above the PR 3
            # supervisor's per-cycle rule: once the tenant's fault plan
            # PROVABLY fired (a rank died with the injected-crash exit
            # code) and was healed inside this round, later rounds run
            # clean — otherwise a resumed trajectory whose epoch index
            # passed the trigger would re-fire the drill every round.
            # A healed restart the drill did NOT cause (evidenced by
            # the exit codes) must not cancel a drill that has yet to
            # reach its trigger.
            fired = any(
                getattr(r, "returncode", None) == FAULT_CRASH_EXIT
                for a in (attempts or [])
                for r in getattr(a, "results", [])
            )
            if fired:
                self._extra_round_env.pop("DCT_FAULT_SPEC", None)
        rec = {
            "mode": "supervised",
            "epochs": self.loop_cfg.epochs_per_round,
            "restarts": res.restarts,
            "classification": res.classification,
        }
        if attempts:
            # Quota accounting: the successful attempt's wall is the
            # round's useful window; everything before it was healing.
            rec["goodput_s"] = round(attempts[-1].wall_seconds, 3)
        if res.classification == "preempted" and not res.success:
            if self._round_preempt.is_set() and not self._stop.is_set():
                # Scheduler lease revocation: the world checkpointed
                # and exited 75 — the round ends early, the loop lives.
                rec["preempted"] = True
                return rec
            # The supervisor itself caught SIGTERM (it forwards our
            # process signals while a round is in flight): the world
            # saved its resume snapshot — drain.
            self.request_stop("preempted")
        elif not res.success:
            self.request_stop(f"train_{res.classification}")
            raise RuntimeError(
                f"supervised round gave up: {res.classification} "
                f"(restarts={res.restarts})"
            )
        return rec

    def _budget_exhausted(self, t0: float) -> str | None:
        lc = self.loop_cfg
        if lc.max_rounds and self.rounds >= lc.max_rounds:
            return "max_rounds"
        if lc.max_wall_s and self._clock() - t0 >= lc.max_wall_s:
            return "max_wall_s"
        if lc.max_promotions and len(
            self.evaluator.promotions
        ) >= lc.max_promotions:
            return "max_promotions"
        return None

    # -- the loop --------------------------------------------------------
    def run(self) -> dict:
        """Run until a stop budget, :meth:`request_stop`, or SIGTERM;
        returns the session summary (also emitted as ``loop.stop``)."""
        from dct_tpu.resilience.preempt import PreemptedError

        lc = self.loop_cfg
        t0 = self._clock()
        self.events.emit(
            "loop", "loop.start",
            train_mode=lc.train_mode,
            epochs_per_round=lc.epochs_per_round,
            endpoint=lc.endpoint,
            poll_s=lc.poll_s, eval_poll_s=lc.eval_poll_s,
            max_rounds=lc.max_rounds, max_wall_s=lc.max_wall_s,
            max_promotions=lc.max_promotions,
        )
        threads = []
        # Stream mode needs no raw_csv — the event log is the source;
        # poll mode keeps the CSV requirement (nothing to watch without
        # a staging file).
        ingest_armed = lc.poll_s > 0 and (
            self.cfg.stream.mode == "stream" or bool(self.cfg.data.raw_csv)
        )
        if ingest_armed:
            # Prime the snapshot BEFORE round 1: a cold start must not
            # race the first fit against an absent parquet.
            self.ingest.check_once()
            t = threading.Thread(
                target=self.ingest.run, args=(self._stop,),
                name="loop-ingest", daemon=True,
            )
            t.start()
            threads.append(t)
        if lc.eval_poll_s > 0:
            t = threading.Thread(
                target=self.evaluator.run, args=(self._stop,),
                name="loop-evaluator", daemon=True,
            )
            t.start()
            threads.append(t)
        if ingest_armed and self.cfg.stream.mode == "stream":
            # Stream cold start: the topic may not exist yet (the
            # producer is its own process and can come up later), so
            # unlike the CSV path there may be NOTHING to prime. Idle
            # at the stream cadence until the first generation
            # publishes instead of crashing round 1 on an absent
            # parquet; the wall/stop budgets still bound the wait.
            from dct_tpu.etl.preprocess import read_etl_state

            while (
                not self._stop.is_set()
                and self._budget_exhausted(t0) is None
                and not read_etl_state(
                    self.cfg.data.processed_dir
                ).get("generation")
            ):
                self._stop.wait(max(self.cfg.stream.poll_s, 0.05))
        error: str | None = None
        try:
            while not self._stop.is_set():
                reason = self._budget_exhausted(t0)
                if reason is not None:
                    self.request_stop(reason)
                    break
                if self._round_gate is not None:
                    # Scheduler mode: block until a round lease is
                    # granted. False = the session is draining (the
                    # scheduler already called request_stop; the
                    # fallback reason covers a gate closing first).
                    try:
                        granted = self._round_gate()
                    except Exception as e:  # noqa: BLE001 — a broken gate stops THIS loop only
                        error = f"{type(e).__name__}: {e}"[:300]
                        self.events.emit(
                            "loop", "loop.error", where="round_gate",
                            error=error,
                        )
                        self.request_stop("gate_error")
                        break
                    if not granted:
                        self.request_stop("gate_closed")
                        break
                self._round_preempt.clear()
                round_t0 = self._clock()
                preempted_round = False
                try:
                    if lc.train_mode == "inline":
                        rec = self._run_round_inline()
                    else:
                        rec = self._run_round_supervised()
                    preempted_round = bool(rec.get("preempted"))
                except PreemptedError:
                    if (
                        self._round_preempt.is_set()
                        and not self._stop.is_set()
                    ):
                        # Scheduler lease revocation (inline round): the
                        # trainer saved a durable resume snapshot — the
                        # round ends early, the loop returns to the
                        # gate. Progress is retained by the resume.
                        rec = {
                            "mode": lc.train_mode,
                            "epochs": lc.epochs_per_round,
                            "preempted": True,
                        }
                        preempted_round = True
                    else:
                        # Inline round honored SIGTERM: resume snapshot
                        # is durable; drain and exit clean.
                        self.request_stop("preempted")
                        break
                except Exception as e:  # noqa: BLE001 — name it, then stop cleanly
                    error = f"{type(e).__name__}: {e}"[:300]
                    self.events.emit(
                        "loop", "loop.error", where="train", error=error
                    )
                    self.request_stop("train_error")
                    if self._on_round is not None:
                        # The scheduler must still release the lease a
                        # failed round was holding.
                        try:
                            self._on_round({"error": error})
                        except Exception:  # noqa: BLE001 — a bad callback must not mask the error
                            pass
                    break
                rec["round_wall_s"] = round(self._clock() - round_t0, 3)
                self.rounds += 1
                if preempted_round:
                    self.preempted_rounds += 1
                rec["round"] = self.rounds
                self.round_results.append(rec)
                self.events.emit("loop", "loop.round", **rec)
                if self._on_round is not None:
                    try:
                        self._on_round(rec)
                    except Exception:  # noqa: BLE001 — a bad callback must not kill the loop
                        pass
        finally:
            self.request_stop("completed")
            for t in threads:
                t.join(timeout=max(60.0, 4 * lc.soak_s + 30.0))
            if error is None and not any(t.is_alive() for t in threads):
                # Drain semantics: whatever the final round published
                # still gets one evaluator pass (bounded: one gate +
                # rollout) — a SIGTERM between checkpoint and promotion
                # must not strand a better model undeployed. Skipped if
                # a join timed out: the evaluator thread may still be
                # mid-pass, and a concurrent second rollout against the
                # same endpoint is worse than a missed final sweep.
                self.evaluator.check_once()
            summary = self.summary(wall_s=self._clock() - t0, error=error)
            self.events.emit("loop", "loop.stop", **summary)
            self.events.close()
        return summary

    def summary(self, *, wall_s: float, error: str | None = None) -> dict:
        promos = self.evaluator.promotions
        fresh = [
            p["freshness_s"] for p in promos
            if p.get("freshness_s") is not None
        ]
        sps = self.train_samples_per_sec_per_chip
        return {
            "reason": self.stop_reason,
            "error": error,
            "rounds": self.rounds,
            "preempted_rounds": self.preempted_rounds,
            "wall_s": round(wall_s, 3),
            "ingested_generations": self.ingest.processed,
            "promotions": len(promos),
            "held": len(self.evaluator.held),
            "evaluator_errors": self.evaluator.errors,
            "ingest_errors": self.ingest.errors,
            "freshness_s": [round(f, 3) for f in fresh],
            "mean_freshness_s": (
                round(sum(fresh) / len(fresh), 3) if fresh else None
            ),
            # Platform goodput: train-step wall as a fraction of loop
            # wall (inline rounds; supervised rounds account in their
            # own rank events).
            "train_step_wall_s": round(self.train_step_wall_s, 3),
            "goodput": (
                round(self.train_step_wall_s / wall_s, 4)
                if wall_s > 0 else None
            ),
            "train_samples_per_sec_per_chip": (
                round(sum(sps) / len(sps), 1) if sps else None
            ),
        }


# ----------------------------------------------------------------------
# The episodic comparator: the SAME primitives, strictly serial.


def run_episodic_cycle(
    cfg: RunConfig,
    *,
    client,
    evaluator,
    clock=time.time,
) -> dict:
    """One serial ETL -> train -> gate -> deploy cycle — the reference's
    episodic DAG semantics built from the loop's own primitives, so the
    ``cycle_freshness`` bench compares architectures, not
    implementations. ``evaluator`` is a
    :class:`~dct_tpu.continuous.evaluator.PromotionEvaluator` reused
    across cycles (its seen-checkpoint state and package counter
    persist, exactly like the loop's)."""
    from dct_tpu.etl.preprocess import preprocess_csv_to_parquet, read_etl_state
    from dct_tpu.train.trainer import Trainer

    t0 = clock()
    preprocess_csv_to_parquet(
        cfg.data.raw_csv, cfg.data.processed_dir, incremental=True
    )
    t_etl = clock()
    result = Trainer(_round_config(cfg, cfg.loop.epochs_per_round)).fit()
    t_train = clock()
    promo = evaluator.check_once()
    t_done = clock()
    state = read_etl_state(cfg.data.processed_dir)
    arrival = state.get("arrival_ts")
    cats = (result.goodput or {}).get("categories") or {}
    return {
        "cycle_s": round(t_done - t0, 4),
        "etl_s": round(t_etl - t0, 4),
        "train_s": round(t_train - t_etl, 4),
        "deploy_s": round(t_done - t_train, 4),
        "train_step_wall_s": float(cats.get("train_step", 0.0)),
        "train_samples_per_sec_per_chip":
            result.steady_samples_per_sec_per_chip,
        "promoted": promo is not None,
        "generation": state.get("generation"),
        "freshness_s": (
            round(t_done - arrival, 4)
            if promo is not None and arrival else None
        ),
        "val_loss": result.val_loss,
    }
