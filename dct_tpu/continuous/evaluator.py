"""Concurrent evaluator: mid-run promotion of the deploy-tier best
checkpoint through the champion/challenger gate.

The episodic platform evaluates and deploys only at cycle end; here a
separate actor watches the deploy tier (``BestLastCheckpointer``'s
atomically-published ``weather-best-*.ckpt``), and for every NEW best:

1. packages it (``serving.score_gen.generate_score_package``) into its
   own challenger dir, with a ``run_info.json`` manifest stamping the
   validation-split parameters, a training-data snapshot for the drift
   detectors, and the ETL generation the checkpoint trained on;
2. runs the full PR 4 rollout — shadow -> gate -> canary -> gate ->
   full — against the LIVE deployed champion via the existing
   :class:`~dct_tpu.deploy.rollout.RolloutOrchestrator`. A gate hold /
   rollback reverts traffic to the champion exactly as in the episodic
   path; training never stops either way.

Freshness accounting: a promoted package's meta carries
``data_generation``/``data_arrival_ts`` (stamped by the trainer from
``etl_state.json``), so each ``loop.promoted`` event reports
``freshness_s`` = promote wall time - data arrival — the number the
``cycle_freshness`` bench leg aggregates.
"""

from __future__ import annotations

import glob
import json
import os
import shutil
import time


def package_checkpoint(
    ckpt_path: str,
    package_dir: str,
    *,
    processed_dir: str | None = None,
    run_id: str | None = None,
) -> dict:
    """Build a challenger deploy package from a raw checkpoint.

    The mid-run analog of ``deploy.rollout.prepare_package`` (which
    queries the tracking store and WIPES its target): here the
    checkpoint is already on local disk and each challenger gets a
    FRESH directory — the deployed champion's package dir must survive
    the next challenger's packaging. Returns the package manifest info
    (generation, split, val metrics).
    """
    from dct_tpu.deploy.rollout import _split_params, _training_data_snapshot
    from dct_tpu.serving.score_gen import generate_score_package

    os.makedirs(package_dir, exist_ok=True)
    meta = generate_score_package(ckpt_path, package_dir)
    info = {
        "run_correlation_id": run_id,
        "val_loss": meta.get("val_loss"),
        "data_generation": meta.get("data_generation"),
        "data_arrival_ts": meta.get("data_arrival_ts"),
        "data_snapshot": _training_data_snapshot(processed_dir),
        # The loop shares the trainer's process env, so the env-derived
        # split parameters ARE the trainer's (checkpoint params carry no
        # split record; the manifest is what the gate trusts).
        "split": _split_params(None),
        "source_checkpoint": os.path.basename(ckpt_path),
    }
    info_path = os.path.join(package_dir, "run_info.json")
    info_tmp = f"{info_path}.tmp.{os.getpid()}"
    with open(info_tmp, "w") as f:
        json.dump(info, f, indent=2)
    os.replace(info_tmp, info_path)
    return info


class PromotionEvaluator:
    """Watches the deploy tier and promotes mid-run.

    ``check_once`` is the unit (poll loops, the episodic comparator and
    tests all share it); :meth:`run` is the thread body. State is one
    (name, mtime_ns, size) triple — the last checkpoint considered —
    so a gate-held checkpoint is not retried until a NEW best lands.
    """

    def __init__(
        self,
        models_dir: str,
        packages_dir: str,
        *,
        client,
        endpoint: str,
        processed_dir: str | None = None,
        soak_s: float = 5.0,
        poll_s: float = 2.0,
        run_id: str | None = None,
        emit=None,
        clock=time.time,
        sleep_fn=time.sleep,
        gate_factory=None,
        keep_packages: int = 4,
        on_promotion=None,
    ):
        self.models_dir = models_dir
        self.packages_dir = packages_dir
        self.client = client
        self.endpoint = endpoint
        self.processed_dir = processed_dir
        self.soak_s = float(soak_s)
        self.poll_s = float(poll_s)
        self.run_id = run_id
        self._emit = emit or (lambda *a, **k: None)
        self._clock = clock
        self._sleep = sleep_fn
        self._gate_factory = gate_factory
        self.keep_packages = int(keep_packages)
        self._on_promotion = on_promotion
        # Package numbering resumes past any EXISTING pkg-* dir: a
        # relaunched loop must never reuse a prior session's package
        # name — the persisted endpoint state may still point a LIVE
        # champion slot at it, and regenerating into that dir would
        # swap the champion's weights for an unvetted challenger's.
        self._counter = self._next_package_index()
        self._seen: tuple | None = None
        # Transient-failure retry budget, PER checkpoint identity: a
        # new best arriving mid-retry must get its own full budget.
        self._retries = 0
        self._retry_key: tuple | None = None
        #: promotion records: {ts, package, generation, freshness_s, ...}
        self.promotions: list[dict] = []
        self.held: list[dict] = []
        self.errors = 0

    def _next_package_index(self) -> int:
        try:
            names = os.listdir(self.packages_dir)
        except OSError:
            return 0
        indices = [
            int(n[4:]) for n in names
            if n.startswith("pkg-") and n[4:].isdigit()
        ]
        return max(indices, default=0)

    # -- deploy-tier watch ---------------------------------------------
    def _newest_best(self) -> tuple[str, tuple] | None:
        """The newest ``weather-best-*.ckpt`` (falling back to any
        non-last ``*.ckpt``) and its stat identity."""
        pats = ("weather-best-*.ckpt", "*.ckpt")
        for pat in pats:
            candidates = [
                p for p in glob.glob(os.path.join(self.models_dir, pat))
                if os.path.basename(p) != "last.ckpt"
            ]
            if not candidates:
                continue
            try:
                newest = max(candidates, key=os.path.getmtime)
                st = os.stat(newest)
            except OSError:
                return None  # replaced mid-glob: next poll retries
            return newest, (os.path.basename(newest), st.st_mtime_ns,
                            st.st_size)
        return None

    def _gate(self):
        if self._gate_factory is not None:
            return self._gate_factory()
        from dct_tpu.evaluation.gates import PromotionGate

        gate = PromotionGate.from_env()
        if gate is not None and self.processed_dir:
            gate.processed_dir = self.processed_dir
        return gate

    # -- one evaluation pass -------------------------------------------
    def check_once(self) -> dict | None:
        """Consider the current best checkpoint; package + gate +
        promote when it is new. Returns the promotion record, or None
        (nothing new / held / errored — held and errored land in their
        own ledgers and events)."""
        found = self._newest_best()
        if found is None:
            return None
        ckpt, key = found
        if key == self._seen:
            return None
        if key != self._retry_key:
            self._retry_key = key
            self._retries = 0
        try:
            rec = self._promote(ckpt)
        except Exception as e:  # noqa: BLE001 — the loop must outlive one bad pass
            self.errors += 1
            # A TRANSIENT failure (disk pressure mid-packaging, tracker
            # hiccup) must not strand a better model undeployed until
            # the next best happens to land: retry this checkpoint a
            # few polls before parking it (a deterministic failure —
            # corrupt checkpoint — must not re-fire every poll forever).
            self._retries += 1
            parked = self._retries >= 3
            if parked:
                self._seen = key
                self._retries = 0
            self._emit(
                "loop", "loop.error",
                where="evaluator", checkpoint=os.path.basename(ckpt),
                parked=parked,
                error=f"{type(e).__name__}: {e}"[:300],
            )
            return None
        self._seen = key
        self._retries = 0
        return rec

    def _promote(self, ckpt: str) -> dict | None:
        from dct_tpu.deploy.rollout import RolloutOrchestrator
        from dct_tpu.evaluation.gates import GateRejection

        self._counter += 1
        pkg = os.path.join(self.packages_dir, f"pkg-{self._counter:05d}")
        info = package_checkpoint(
            ckpt, pkg,
            processed_dir=self.processed_dir, run_id=self.run_id,
        )
        orch = RolloutOrchestrator(
            self.client, self.endpoint,
            soak_seconds=self.soak_s, sleep_fn=self._sleep,
            run_id=self.run_id, gate=self._gate(),
        )
        t0 = self._clock()
        try:
            orch.run(pkg)
        except GateRejection as rej:
            rec = {
                "ts": self._clock(),
                "package": pkg,
                "checkpoint": os.path.basename(ckpt),
                "decision": rej.decision.decision,
                "stage": rej.decision.stage,
                "reason": rej.decision.reason,
            }
            self.held.append(rec)
            self._emit(
                "loop", "loop.promotion_held",
                checkpoint=rec["checkpoint"], decision=rec["decision"],
                stage=rec["stage"], reason=rec["reason"],
            )
            self._prune_packages()
            return None
        now = self._clock()
        arrival = info.get("data_arrival_ts")
        rec = {
            "ts": now,
            "package": pkg,
            "checkpoint": os.path.basename(ckpt),
            "generation": info.get("data_generation"),
            "freshness_s": (
                round(now - arrival, 4) if arrival else None
            ),
            "rollout_s": round(now - t0, 4),
            "val_loss": info.get("val_loss"),
        }
        self.promotions.append(rec)
        self._emit(
            "loop", "loop.promoted",
            checkpoint=rec["checkpoint"],
            generation=rec["generation"],
            freshness_s=rec["freshness_s"],
            rollout_s=rec["rollout_s"],
            promotions=len(self.promotions),
        )
        if self._on_promotion is not None:
            try:
                self._on_promotion(rec)
            except Exception:  # noqa: BLE001 — a bad callback must not kill the loop
                pass
        self._prune_packages()
        return rec

    def _prune_packages(self) -> None:
        """Bound disk: drop challenger dirs that no endpoint slot
        references, keeping the newest ``keep_packages`` regardless
        (a just-held package may still be under operator triage)."""
        try:
            dirs = sorted(glob.glob(os.path.join(self.packages_dir, "pkg-*")))
        except OSError:
            return
        live = set()
        resolver = getattr(self.client, "deployment_package_dir", None)
        if resolver is not None:
            try:
                for slot in self.client.list_deployments(self.endpoint):
                    p = resolver(self.endpoint, slot)
                    if p:
                        live.add(os.path.abspath(p))
            except Exception:  # noqa: BLE001 — pruning is hygiene, never fatal
                return
        for d in dirs[: -self.keep_packages or None]:
            if os.path.abspath(d) in live:
                continue
            shutil.rmtree(d, ignore_errors=True)

    def run(self, stop_event) -> None:
        """Thread body: poll until ``stop_event`` is set. The pass in
        flight when the stop lands completes (a half-run rollout would
        leave traffic mid-flip); the loop's drain joins this thread."""
        while not stop_event.is_set():
            self.check_once()
            stop_event.wait(self.poll_s)
