"""Always-on overlapped cycles (ROADMAP item 3 / ISSUE 10).

The episodic platform runs ETL -> train -> gate -> deploy strictly
serially, once per DAG trigger — data-to-deployed-model latency is the
SUM of every stage and the chips idle through everything but the train
stage. This package is the Podracer-style restructuring (PAPERS.md):
the same stages as concurrently-running actors over shared, atomically-
published artifacts:

- :class:`~dct_tpu.continuous.ingest.IngestWatcher` — content-digest
  polling of the raw staging CSV; a change triggers the incremental ETL
  (``etl/preprocess.py``) while training keeps running;
- the training pump (:class:`~dct_tpu.continuous.loop.AlwaysOnLoop`) —
  short rounds that EXTEND one optimizer trajectory (``DCT_RESUME``
  semantics), each under the PR 3 supervisor (or inline for benches);
- :class:`~dct_tpu.continuous.evaluator.PromotionEvaluator` — watches
  the deploy-tier best checkpoint, packages each new one, consults the
  PR 4 champion/challenger gate against the LIVE deployed champion, and
  promotes mid-run through the existing
  :class:`~dct_tpu.deploy.rollout.RolloutOrchestrator` — no training
  stop, no cycle boundary.

The train hot path is untouched: per-step semantics are bit-identical
to the serial trainer (pinned by tests/test_continuous.py — loss
trajectories and checkpoint bytes). docs/CONTINUOUS.md has the
architecture, promotion semantics, and failure modes.
"""

from dct_tpu.continuous.evaluator import PromotionEvaluator, package_checkpoint
from dct_tpu.continuous.ingest import IngestWatcher
from dct_tpu.continuous.loop import AlwaysOnLoop, run_episodic_cycle

__all__ = [
    "AlwaysOnLoop",
    "IngestWatcher",
    "PromotionEvaluator",
    "package_checkpoint",
    "run_episodic_cycle",
]
