"""Ingest watcher: content-digest polling of the raw staging path.

The episodic DAG runs ETL once per trigger whether or not the data
changed; the watcher inverts that — it polls the staging CSV on a
cadence (cheap ``stat`` pre-check, so an idle loop costs two syscalls
per poll) and hands any change to the incremental ETL
(:func:`dct_tpu.etl.preprocess.preprocess_csv_to_parquet`), which
digests the content and decides no-op / append-only delta / full
rebuild. ETL therefore runs CONCURRENTLY with training: by the time the
trainer's next round starts, the fresh generation is already published.

Events (``ingest`` component, documented in docs/OBSERVABILITY.md):
``ingest.detected`` when the stat pre-check sees a change,
``ingest.processed`` when a generation was actually published (mode,
rows, etl seconds), ``ingest.error`` when the ETL raised.
"""

from __future__ import annotations

import os
import time


class IngestWatcher:
    """Polls ``raw_csv`` and feeds the incremental ETL.

    Single-consumer by design: one watcher owns the processed dir's
    etl_state. ``check_once`` is the unit (poll loops and tests share
    it); :meth:`run` is the thread body.
    """

    def __init__(
        self,
        raw_csv: str,
        processed_dir: str,
        *,
        poll_s: float = 2.0,
        emit=None,
        clock=time.time,
    ):
        self.raw_csv = raw_csv
        self.processed_dir = processed_dir
        self.poll_s = float(poll_s)
        self._emit = emit or (lambda *a, **k: None)
        self._clock = clock
        self._last_stat: tuple | None = None
        self._retries = 0
        self.processed = 0
        self.errors = 0

    def _stat(self) -> tuple | None:
        try:
            st = os.stat(self.raw_csv)
        except OSError:
            return None
        return (st.st_size, st.st_mtime_ns)

    def check_once(self) -> dict | None:
        """One poll: stat pre-check, then the incremental ETL on any
        change. Returns the published etl_state when a generation was
        processed, None otherwise (no data / unchanged / ETL no-op)."""
        cur = self._stat()
        if cur is None or cur == self._last_stat:
            return None
        self._emit(
            "ingest", "ingest.detected",
            path=self.raw_csv, size=cur[0],
        )
        from dct_tpu.etl.preprocess import (
            preprocess_csv_to_parquet, read_etl_state,
        )

        before = read_etl_state(self.processed_dir).get("generation", 0)
        t0 = self._clock()
        try:
            preprocess_csv_to_parquet(
                self.raw_csv, self.processed_dir, incremental=True
            )
        except Exception as e:  # noqa: BLE001 — the loop must outlive one bad poll
            self.errors += 1
            # Transient failures (disk pressure mid-publish, a reader
            # race) retry on the next polls; only a persistent failure
            # parks this content's stat — a permanently-broken file
            # must not re-parse every poll, while any FIX changes the
            # stat (mtime_ns at minimum) and is picked up.
            self._retries += 1
            parked = self._retries >= 3
            if parked:
                self._last_stat = cur
                self._retries = 0
            self._emit(
                "ingest", "ingest.error",
                parked=parked,
                error=f"{type(e).__name__}: {e}"[:300],
            )
            return None
        self._last_stat = cur
        self._retries = 0
        state = read_etl_state(self.processed_dir)
        if state.get("generation", 0) == before:
            return None  # content digest said no-op (mtime-only touch)
        self.processed += 1
        self._emit(
            "ingest", "ingest.processed",
            generation=state.get("generation"),
            mode=state.get("mode"),
            rows=state.get("rows"),
            rows_delta=state.get("rows_delta"),
            etl_s=round(self._clock() - t0, 4),
            arrival_ts=state.get("arrival_ts"),
        )
        return state

    def run(self, stop_event) -> None:
        """Thread body: poll until ``stop_event`` is set."""
        while not stop_event.is_set():
            self.check_once()
            stop_event.wait(self.poll_s)
