"""Ingest watchers: the continuous loop's data-arrival edge.

The episodic DAG runs ETL once per trigger whether or not the data
changed; the watchers invert that. Two modes (``DCT_INGEST_MODE``):

- :class:`IngestWatcher` (``poll``, the default): polls the staging
  CSV on a cadence (cheap ``stat`` pre-check, so an idle loop costs
  two syscalls per poll) and hands any change to the incremental ETL
  (:func:`dct_tpu.etl.preprocess.preprocess_csv_to_parquet`), which
  digests the content and decides no-op / append-only delta / full
  rebuild.
- :class:`StreamIngestWatcher` (``stream``): consumes the partitioned
  event log (:mod:`dct_tpu.stream`) through a durable consumer group
  and runs the exactly-once stream ETL
  (:func:`dct_tpu.stream.stream_etl.stream_etl_pass`) — one pass per
  committed offset range, with a background prefetcher staging the
  next span off the log while the trainer dispatches.

Either way ETL runs CONCURRENTLY with training: by the time the
trainer's next round starts, the fresh generation is already
published. Both watchers share one interface (``check_once`` /
``run`` / ``processed`` / ``errors``), so the loop is mode-blind.

Events (``ingest`` component, documented in docs/OBSERVABILITY.md):
``ingest.detected`` when the pre-check sees a change (stream mode
reports pending record/second lag instead of file size),
``ingest.processed`` when a generation was actually published (mode,
rows, etl seconds), ``ingest.error`` when the ETL raised.
"""

from __future__ import annotations

import os
import time


class IngestWatcher:
    """Polls ``raw_csv`` and feeds the incremental ETL.

    Single-consumer by design: one watcher owns the processed dir's
    etl_state. ``check_once`` is the unit (poll loops and tests share
    it); :meth:`run` is the thread body.
    """

    def __init__(
        self,
        raw_csv: str,
        processed_dir: str,
        *,
        poll_s: float = 2.0,
        emit=None,
        clock=time.time,
    ):
        self.raw_csv = raw_csv
        self.processed_dir = processed_dir
        self.poll_s = float(poll_s)
        self._emit = emit or (lambda *a, **k: None)
        self._clock = clock
        self._last_stat: tuple | None = None
        self._retries = 0
        self.processed = 0
        self.errors = 0

    def _stat(self) -> tuple | None:
        try:
            st = os.stat(self.raw_csv)
        except OSError:
            return None
        return (st.st_size, st.st_mtime_ns)

    def check_once(self) -> dict | None:
        """One poll: stat pre-check, then the incremental ETL on any
        change. Returns the published etl_state when a generation was
        processed, None otherwise (no data / unchanged / ETL no-op)."""
        cur = self._stat()
        if cur is None or cur == self._last_stat:
            return None
        self._emit(
            "ingest", "ingest.detected",
            path=self.raw_csv, size=cur[0],
        )
        from dct_tpu.etl.preprocess import (
            preprocess_csv_to_parquet, read_etl_state,
        )

        before = read_etl_state(self.processed_dir).get("generation", 0)
        t0 = self._clock()
        try:
            preprocess_csv_to_parquet(
                self.raw_csv, self.processed_dir, incremental=True
            )
        except Exception as e:  # noqa: BLE001 — the loop must outlive one bad poll
            self.errors += 1
            # Transient failures (disk pressure mid-publish, a reader
            # race) retry on the next polls; only a persistent failure
            # parks this content's stat — a permanently-broken file
            # must not re-parse every poll, while any FIX changes the
            # stat (mtime_ns at minimum) and is picked up.
            self._retries += 1
            parked = self._retries >= 3
            if parked:
                self._last_stat = cur
                self._retries = 0
            self._emit(
                "ingest", "ingest.error",
                parked=parked,
                error=f"{type(e).__name__}: {e}"[:300],
            )
            return None
        self._last_stat = cur
        self._retries = 0
        state = read_etl_state(self.processed_dir)
        if state.get("generation", 0) == before:
            return None  # content digest said no-op (mtime-only touch)
        self.processed += 1
        self._emit(
            "ingest", "ingest.processed",
            generation=state.get("generation"),
            mode=state.get("mode"),
            rows=state.get("rows"),
            rows_delta=state.get("rows_delta"),
            etl_s=round(self._clock() - t0, 4),
            arrival_ts=state.get("arrival_ts"),
        )
        return state

    def run(self, stop_event) -> None:
        """Thread body: poll until ``stop_event`` is set."""
        while not stop_event.is_set():
            self.check_once()
            stop_event.wait(self.poll_s)


class StreamIngestWatcher:
    """Consumes the partitioned event log and feeds the stream ETL.

    Drop-in for :class:`IngestWatcher` on the loop side (``check_once``
    / ``run`` / ``processed`` / ``errors``), but the change pre-check
    is consumer-group lag instead of a file stat, and processing is the
    exactly-once offset-range pass instead of a CSV re-digest. A
    :class:`~dct_tpu.stream.prefetch.StreamPrefetcher` stages the next
    span off the log in the background so the pass overlaps training
    dispatch.

    ``stream_cfg`` is a :class:`dct_tpu.config.StreamConfig` (duck-typed
    in tests). When ``metrics_dir`` is set the watcher owns a registry +
    :class:`~dct_tpu.observability.aggregate.SnapshotPublisher` so the
    ``dct_stream_*`` series reach the metrics plane (and, via the
    publisher's history hook, the telemetry store).
    """

    def __init__(
        self,
        stream_cfg,
        processed_dir: str,
        *,
        poll_s: float = 2.0,
        metrics_dir: str = "",
        prefetch: bool = True,
        emit=None,
        clock=time.time,
    ):
        self.cfg = stream_cfg
        self.processed_dir = processed_dir
        self.poll_s = float(poll_s)
        self.metrics_dir = metrics_dir
        self._prefetch_enabled = bool(prefetch)
        self._emit = emit or (lambda *a, **k: None)
        self._clock = clock
        self._log = None
        self._consumer = None
        self._prefetcher = None
        self._publisher = None
        self._retries = 0
        self.processed = 0
        self.errors = 0

    def _ensure(self) -> bool:
        """Lazily open the log + consumer. Returns False while the topic
        does not exist yet (producer not started) — a cheap idle poll,
        mirroring the CSV watcher's missing-file stat."""
        if self._consumer is not None:
            return True
        if not os.path.isdir(os.path.join(self.cfg.dir, self.cfg.topic)):
            return False
        from dct_tpu.stream.consumer import ConsumerGroup
        from dct_tpu.stream.log import PartitionedEventLog
        from dct_tpu.stream.prefetch import StreamPrefetcher

        registry = None
        if self.metrics_dir:
            from dct_tpu.observability.aggregate import SnapshotPublisher
            from dct_tpu.observability.metrics import MetricsRegistry

            registry = MetricsRegistry()
            self._publisher = SnapshotPublisher(
                registry, self.metrics_dir,
                proc=f"stream-{self.cfg.group}", clock=self._clock,
            )
        self._log = PartitionedEventLog(
            self.cfg.dir, self.cfg.topic, readonly=True,
            emit=self._emit, clock=self._clock,
        )
        self._consumer = ConsumerGroup(
            self._log, self.cfg.group,
            emit=self._emit, clock=self._clock, registry=registry,
        )
        if self._prefetch_enabled:
            self._prefetcher = StreamPrefetcher(
                self._log, self.cfg.group,
                span_records=self.cfg.max_batch, clock=self._clock,
            ).start()
        return True

    def check_once(self) -> dict | None:
        """One poll: lag pre-check, then the exactly-once ETL pass on
        any pending records. Returns the published etl_state when a
        generation was processed, None otherwise."""
        if not self._ensure():
            return None
        lag = self._consumer.lag()  # also refreshes the lag gauges
        if lag["records"] <= 0:
            if self._publisher is not None:
                self._publisher.maybe_publish()
            return None
        self._emit(
            "ingest", "ingest.detected",
            source="stream", topic=self.cfg.topic, group=self.cfg.group,
            lag_records=lag["records"], lag_seconds=round(lag["seconds"], 4),
        )
        from dct_tpu.stream.stream_etl import stream_etl_pass

        records = None
        if self._prefetcher is not None:
            records = self._prefetcher.take(self.cfg.max_batch)
        t0 = self._clock()
        try:
            state = stream_etl_pass(
                self._consumer, self.processed_dir,
                max_records=self.cfg.max_batch, records=records,
                emit=self._emit, clock=self._clock,
            )
        except Exception as e:  # noqa: BLE001 — the loop must outlive one bad pass
            self.errors += 1
            # Unlike the CSV watcher there is nothing to park: the
            # uncommitted range replays on the next poll, and exactly-
            # once semantics make the retry free of duplicates.
            self._retries += 1
            self._emit(
                "ingest", "ingest.error",
                source="stream", retries=self._retries,
                error=f"{type(e).__name__}: {e}"[:300],
            )
            return None
        self._retries = 0
        if self._publisher is not None:
            self._publisher.maybe_publish()
        if state is None:
            return None
        self.processed += 1
        self._emit(
            "ingest", "ingest.processed",
            source="stream",
            generation=state.get("generation"),
            mode=state.get("mode"),
            rows=state.get("rows"),
            rows_delta=state.get("rows_delta"),
            etl_s=round(self._clock() - t0, 4),
            arrival_ts=state.get("arrival_ts"),
        )
        return state

    def run(self, stop_event) -> None:
        """Thread body: poll until ``stop_event`` is set. Under
        sustained arrivals passes run BACK-TO-BACK (a processed pass
        re-checks immediately — sleeping with a backlog pending would
        add ``poll_s`` to every event's arrival→trainable lag); the
        cadence wait only happens when the group is caught up."""
        while not stop_event.is_set():
            if self.check_once() is None:
                stop_event.wait(self.poll_s)
        self.close()

    def close(self) -> None:
        if self._prefetcher is not None:
            self._prefetcher.stop()
        if self._publisher is not None:
            self._publisher.close(final=True)
        if self._log is not None:
            self._log.close()
